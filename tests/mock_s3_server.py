"""Minimal in-process S3 server for tests (reference:
python/ray/tests/mock_s3_server.py — same role, implemented against the
subset of the S3 REST API that pyarrow.fs.S3FileSystem uses: HeadBucket,
HeadObject, GetObject (with Range), PutObject, DeleteObject, ListObjectsV2,
CreateBucket, and single-shot multipart upload)."""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse


class _S3State:
    def __init__(self):
        self.buckets: dict = {}  # bucket -> {key: bytes}
        self.uploads: dict = {}  # upload_id -> {part_number: bytes}
        self.lock = threading.Lock()
        self._next_upload = 0


def _xml(body: str) -> bytes:
    return ('<?xml version="1.0" encoding="UTF-8"?>' + body).encode()


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    state: _S3State = None  # type: ignore[assignment]

    def log_message(self, *a):  # quiet
        pass

    def _split(self):
        parsed = urlparse(self.path)
        parts = unquote(parsed.path).lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        return bucket, key, parse_qs(parsed.query, keep_blank_values=True)

    def _reply(self, code: int, body: bytes = b"", headers=None):
        self.send_response(code)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _read_body(self) -> bytes:
        n = int(self.headers.get("Content-Length", 0) or 0)
        if self.headers.get("Transfer-Encoding") == "chunked":
            out = b""
            while True:
                size = int(self.rfile.readline().strip().split(b";")[0], 16)
                if size == 0:
                    self.rfile.readline()
                    break
                out += self.rfile.read(size)
                self.rfile.readline()
            raw = out
        else:
            raw = self.rfile.read(n)
        if "aws-chunked" in (self.headers.get("Content-Encoding") or ""):
            # SigV4 streaming payload: hex-size[;chunk-signature=..]\r\n data
            # \r\n ... 0[;sig]\r\n trailers. Decode to the real object bytes.
            out = b""
            pos = 0
            while pos < len(raw):
                nl = raw.index(b"\r\n", pos)
                size = int(raw[pos:nl].split(b";")[0], 16)
                if size == 0:
                    break
                start = nl + 2
                out += raw[start : start + size]
                pos = start + size + 2  # skip trailing \r\n
            return out
        return raw

    def _not_found(self, what="NoSuchKey"):
        self._reply(
            404, _xml(f"<Error><Code>{what}</Code></Error>"),
            headers={"Content-Type": "application/xml"},
        )

    def do_HEAD(self):
        bucket, key, _ = self._split()
        with self.state.lock:
            b = self.state.buckets.get(bucket)
            if b is None:
                return self._not_found("NoSuchBucket")
            if not key:  # HeadBucket
                return self._reply(200)
            if key in b:
                return self._head_object(b[key])
            return self._not_found()

    def _head_object(self, data: bytes):
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.send_header("ETag", '"mock"')
        self.send_header("Last-Modified", "Thu, 01 Jan 1970 00:00:00 GMT")
        self.send_header("Accept-Ranges", "bytes")
        self.end_headers()

    def do_GET(self):
        bucket, key, q = self._split()
        with self.state.lock:
            b = self.state.buckets.get(bucket)
            if b is None:
                return self._not_found("NoSuchBucket")
            if not key:  # ListObjectsV2
                prefix = q.get("prefix", [""])[0]
                delim = q.get("delimiter", [""])[0]
                keys = sorted(k for k in b if k.startswith(prefix))
                contents, prefixes = [], set()
                for k in keys:
                    if delim:
                        rest = k[len(prefix):]
                        if delim in rest:
                            prefixes.add(prefix + rest.split(delim)[0] + delim)
                            continue
                    contents.append(k)
                items = "".join(
                    f"<Contents><Key>{k}</Key><Size>{len(b[k])}</Size>"
                    "<LastModified>1970-01-01T00:00:00.000Z</LastModified>"
                    '<ETag>"mock"</ETag></Contents>'
                    for k in contents
                )
                cps = "".join(
                    f"<CommonPrefixes><Prefix>{p}</Prefix></CommonPrefixes>"
                    for p in sorted(prefixes)
                )
                body = _xml(
                    "<ListBucketResult>"
                    f"<Name>{bucket}</Name><Prefix>{prefix}</Prefix>"
                    f"<KeyCount>{len(contents) + len(prefixes)}</KeyCount>"
                    f"<IsTruncated>false</IsTruncated>{items}{cps}"
                    "</ListBucketResult>"
                )
                return self._reply(
                    200, body, headers={"Content-Type": "application/xml"}
                )
            data = b.get(key)
            if data is None:
                return self._not_found()
            rng = self.headers.get("Range")
            if rng and rng.startswith("bytes="):
                lo_s, _, hi_s = rng[len("bytes="):].partition("-")
                lo = int(lo_s)
                hi = int(hi_s) if hi_s else len(data) - 1
                part = data[lo : hi + 1]
                return self._reply(
                    206,
                    part,
                    headers={
                        "Content-Range": f"bytes {lo}-{lo+len(part)-1}/{len(data)}",
                        "ETag": '"mock"',
                        "Accept-Ranges": "bytes",
                    },
                )
            return self._reply(
                200, data, headers={"ETag": '"mock"', "Accept-Ranges": "bytes"}
            )

    def do_PUT(self):
        bucket, key, q = self._split()
        body = self._read_body()
        with self.state.lock:
            if not key:  # CreateBucket
                self.state.buckets.setdefault(bucket, {})
                return self._reply(200)
            b = self.state.buckets.setdefault(bucket, {})
            if "partNumber" in q and "uploadId" in q:
                uid = q["uploadId"][0]
                self.state.uploads.setdefault(uid, {})[
                    int(q["partNumber"][0])
                ] = body
                return self._reply(200, headers={"ETag": '"mock-part"'})
            b[key] = body
            return self._reply(200, headers={"ETag": '"mock"'})

    def do_POST(self):
        bucket, key, q = self._split()
        body = self._read_body()
        with self.state.lock:
            if "uploads" in q:  # CreateMultipartUpload
                self.state._next_upload += 1
                uid = f"upload-{self.state._next_upload}"
                self.state.uploads[uid] = {}
                return self._reply(
                    200,
                    _xml(
                        "<InitiateMultipartUploadResult>"
                        f"<Bucket>{bucket}</Bucket><Key>{key}</Key>"
                        f"<UploadId>{uid}</UploadId>"
                        "</InitiateMultipartUploadResult>"
                    ),
                    headers={"Content-Type": "application/xml"},
                )
            if "uploadId" in q:  # CompleteMultipartUpload
                uid = q["uploadId"][0]
                parts = self.state.uploads.pop(uid, {})
                data = b"".join(parts[i] for i in sorted(parts))
                self.state.buckets.setdefault(bucket, {})[key] = data
                return self._reply(
                    200,
                    _xml(
                        "<CompleteMultipartUploadResult>"
                        f"<Bucket>{bucket}</Bucket><Key>{key}</Key>"
                        '<ETag>"mock"</ETag>'
                        "</CompleteMultipartUploadResult>"
                    ),
                    headers={"Content-Type": "application/xml"},
                )
            if "delete" in q:  # DeleteObjects (batch)
                import re

                b = self.state.buckets.setdefault(bucket, {})
                deleted = []
                for m in re.finditer(rb"<Key>([^<]+)</Key>", body):
                    k = unquote(m.group(1).decode())
                    b.pop(k, None)
                    deleted.append(k)
                return self._reply(
                    200,
                    _xml(
                        "<DeleteResult>"
                        + "".join(
                            f"<Deleted><Key>{k}</Key></Deleted>" for k in deleted
                        )
                        + "</DeleteResult>"
                    ),
                    headers={"Content-Type": "application/xml"},
                )
        self._reply(400)

    def do_DELETE(self):
        bucket, key, q = self._split()
        with self.state.lock:
            if "uploadId" in q:
                self.state.uploads.pop(q["uploadId"][0], None)
                return self._reply(204)
            b = self.state.buckets.get(bucket)
            if b is None:
                return self._not_found("NoSuchBucket")
            if not key:
                self.state.buckets.pop(bucket, None)
                return self._reply(204)
            b.pop(key, None)
            return self._reply(204)


class MockS3Server:
    """Start with `with MockS3Server() as srv:`; srv.endpoint is the
    http://host:port to point S3 clients at."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        state = _S3State()
        handler = type("BoundHandler", (_Handler,), {"state": state})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.state = state
        self.endpoint = f"http://{host}:{self.httpd.server_address[1]}"
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self.httpd.shutdown()
        self.httpd.server_close()

    def create_bucket(self, name: str) -> None:
        with self.state.lock:
            self.state.buckets.setdefault(name, {})
