"""conda + container runtime envs (reference:
python/ray/_private/runtime_env/conda.py + container.py). Both runtimes are
exercised through fake executables on PATH — the same injectable-runner
pattern the GCE provider tests use — so the full worker path runs without
conda/podman installed."""

import os
import stat
import sys
import textwrap

import pytest

import ray_tpu
from ray_tpu.runtime_env.container import build_container_argv


# -- container argv construction (unit) --------------------------------------


def test_container_argv_shape(tmp_path):
    argv = build_container_argv(
        {"image": "rayproject/ray:latest", "run_options": ["--cpus=2"]},
        [sys.executable, "-m", "ray_tpu._private.worker_main"],
        {"RAY_TPU_NODE_ID": "abc", "RAY_TPU_WORKER_ID": "w1"},
        runtime="/usr/bin/podman",
    )
    assert argv[0] == "/usr/bin/podman"
    assert argv[1] == "run"
    assert "--network=host" in argv
    assert "--env" in argv and "RAY_TPU_NODE_ID=abc" in argv
    assert "--cpus=2" in argv
    img = argv.index("rayproject/ray:latest")
    # Inside the image: the image's python, then the worker module.
    assert argv[img + 1 :] == ["python3", "-m", "ray_tpu._private.worker_main"]
    with pytest.raises(ValueError):
        build_container_argv({}, [sys.executable], {}, runtime="podman")


def _write_exe(path, body: str) -> str:
    path.write_text(body)
    path.chmod(path.stat().st_mode | stat.S_IEXEC)
    return str(path)


# -- conda env provisioning through a fake conda binary ----------------------


@pytest.fixture
def fake_conda_path(tmp_path):
    """A `conda` shim implementing `conda env create -p <prefix> -f <yaml>`:
    creates the prefix with a site-packages containing a marker module whose
    content records the env name from the yaml."""
    bindir = tmp_path / "bin"
    bindir.mkdir()
    _write_exe(
        bindir / "conda",
        textwrap.dedent(
            f"""\
            #!{sys.executable}
            import os, sys
            args = sys.argv[1:]
            assert args[0:2] == ["env", "create"], args
            prefix = args[args.index("-p") + 1]
            site = os.path.join(
                prefix, "lib",
                f"python{{sys.version_info.major}}.{{sys.version_info.minor}}",
                "site-packages",
            )
            os.makedirs(site, exist_ok=True)
            with open(os.path.join(site, "conda_marker.py"), "w") as f:
                f.write("PROVISIONED_BY = 'fake-conda'\\n")
            with open(os.path.join(prefix, ".provisioned"), "w") as f:
                f.write("ok")
            """
        ),
    )
    return str(bindir)


def test_conda_env_provisioned_and_activated(tmp_path, fake_conda_path, monkeypatch):
    """ensure_conda_env drives the conda binary once (cached after), and
    activation puts the env's site-packages on sys.path."""
    import asyncio

    monkeypatch.setenv("PATH", fake_conda_path + os.pathsep + os.environ["PATH"])
    from ray_tpu.runtime_env import context as ctx

    monkeypatch.setattr(ctx, "EXTRACT_ROOT", str(tmp_path / "envs"))
    spec = {"dependencies": ["python=3.12", "numpy"]}
    prefix = asyncio.run(ctx.ensure_conda_env(spec))
    assert os.path.exists(os.path.join(prefix, ".provisioned"))
    # Cached: a second call returns without re-invoking conda.
    assert asyncio.run(ctx.ensure_conda_env(spec)) == prefix
    site = ctx._conda_site_packages(prefix)
    assert os.path.exists(os.path.join(site, "conda_marker.py"))


def test_worker_boots_in_conda_env(shutdown_only, tmp_path, fake_conda_path):
    """E2E: an actor with runtime_env={'conda': ...} runs in a worker whose
    sys.path contains the provisioned env — the marker module imports."""
    ray_tpu.init(
        num_cpus=2,
        num_tpus=0,
        worker_env={
            "PATH": fake_conda_path + os.pathsep + os.environ["PATH"],
        },
    )

    @ray_tpu.remote(runtime_env={"conda": {"dependencies": ["numpy"]}})
    class CondaActor:
        def probe(self):
            import conda_marker

            return conda_marker.PROVISIONED_BY

    a = CondaActor.remote()
    assert ray_tpu.get(a.probe.remote()) == "fake-conda"
    ray_tpu.kill(a)

    # Tasks apply conda the same way.
    @ray_tpu.remote(runtime_env={"conda": {"dependencies": ["numpy"]}})
    def probe_task():
        import conda_marker

        return conda_marker.PROVISIONED_BY

    assert ray_tpu.get(probe_task.remote()) == "fake-conda"


# -- containerized worker through a fake podman binary -----------------------


@pytest.fixture
def fake_podman_path(tmp_path):
    """A `podman` shim that strips the container argv and execs the inner
    worker command with the host python — proving the raylet built a
    correct `podman run` line and that a worker booted through it."""
    bindir = tmp_path / "cbin"
    bindir.mkdir()
    _write_exe(
        bindir / "podman",
        textwrap.dedent(
            f"""\
            #!{sys.executable}
            import os, sys
            args = sys.argv[1:]
            assert args[0] == "run", args
            env = dict(os.environ)
            i = 1
            image = None
            while i < len(args):
                a = args[i]
                if a == "--env":
                    k, _, v = args[i + 1].partition("=")
                    env[k] = v
                    i += 2
                elif a == "-v":
                    i += 2
                elif a.startswith("-"):
                    i += 1
                else:
                    image = a
                    break
            assert image == "fake/image:1", image
            env["RAY_TPU_CONTAINERIZED"] = "1"
            inner = args[i + 1 :]
            # image python3 -> host python (the shim IS the container).
            inner[0] = sys.executable
            os.execve(inner[0], inner, env)
            """
        ),
    )
    return str(bindir)


def test_actor_worker_boots_in_container(shutdown_only, tmp_path, fake_podman_path):
    ray_tpu.init(
        num_cpus=2,
        num_tpus=0,
        worker_env={"PATH": fake_podman_path + os.pathsep + os.environ["PATH"]},
    )
    # The raylet discovers the container runtime on ITS PATH.
    os.environ["PATH"] = fake_podman_path + os.pathsep + os.environ["PATH"]

    @ray_tpu.remote(runtime_env={"container": {"image": "fake/image:1"}})
    class Boxed:
        def probe(self):
            return os.environ.get("RAY_TPU_CONTAINERIZED")

    a = Boxed.remote()
    assert ray_tpu.get(a.probe.remote()) == "1"
    ray_tpu.kill(a)
