"""Highly-available control plane (gcs_ha.py + replicated gcs_store):
warm-standby failover, epoch-fenced leadership, leader-file re-targeting,
and the resubscribe/term protocol that keeps clients consistent across a
promotion (docs/fault_tolerance.md "HA deployment")."""

import asyncio
import os
import time

import pytest

import ray_tpu
from ray_tpu._private import gcs_ha, rpc
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.common import config
from ray_tpu._private.gcs import GcsClient, GcsServer
from ray_tpu._private.gcs_store import drop_host


@pytest.fixture
def ha_env(monkeypatch, tmp_path):
    monkeypatch.setenv("RAY_TPU_GCS_PERSIST_BACKEND", "replicated")
    monkeypatch.setenv("RAY_TPU_GCS_LEADER_LEASE_S", "1.0")
    monkeypatch.setenv("RAY_TPU_GCS_STANDBY_POLL_S", "0.05")
    config.refresh()
    yield str(tmp_path / "gcs.wal")
    # Undo the env BEFORE refreshing: monkeypatch's own teardown runs after
    # this fixture body, which would leave the restored env uncached.
    monkeypatch.undo()
    config.refresh()


def test_failover_preserves_state_and_retargets_clients(ha_env):
    """Tentpole e2e: primary dies WITH its disk; the warm standby promotes
    from the follower log at term+1, the leader file flips, and a client
    with a file resolver re-targets — acknowledged state fully intact."""
    path = ha_env
    leader_file = gcs_ha.leader_file_path(path)

    async def go():
        primary = GcsServer(session_name="ha", persist_path=path,
                            persist_backend="replicated")
        await primary.start()
        assert primary.leader_term == 1
        standby = gcs_ha.GcsStandby(session_name="ha", persist_path=path)
        await standby.start()

        conn = await rpc.connect(*primary.server.address)
        client = GcsClient(conn, resolver=gcs_ha.file_resolver(leader_file))
        await client.call("KVPut", {"ns": "", "key": "k", "value": b"v"})

        await primary.crash()
        drop_host(path)  # the primary's machine (and log member) is gone
        await asyncio.wait_for(standby.promoted.wait(), 30)
        new = standby.server
        assert new.leader_term == 2
        assert gcs_ha.resolve_leader_file(leader_file) == new.server.address

        # The same client object follows the leader file to the new server.
        reply = await client.call("KVGet", {"ns": "", "key": "k"},
                                  timeout=30)
        assert reply.get("value") == b"v"
        lead = gcs_ha.read_leadership(new.store)
        assert lead["term"] == 2

        await client.close()
        await standby.stop()

    asyncio.run(go())


def test_fenced_old_primary_rejects_writes_and_demotes(ha_env, monkeypatch):
    """Satellite (c): a partitioned old primary that keeps writing after its
    lease expired gets every write rejected with a typed StaleLeaderError,
    never pollutes the new leader's tables, and exits its serve loop."""
    # A huge lease suppresses the old primary's own renewal beat, so the
    # test (not a background timer) drives the first fenced write.
    monkeypatch.setenv("RAY_TPU_GCS_LEADER_LEASE_S", "60")
    config.refresh()
    path = ha_env

    async def go():
        old = GcsServer(session_name="ha", persist_path=path,
                        persist_backend="replicated")
        await old.start()
        conn = await rpc.connect(*old.server.address)  # raw: no retry wrap
        await conn.call("KVPut", {"ns": "", "key": "pre", "value": b"1"})

        # "Partition": a new leader is elected elsewhere while the old
        # process still serves. Opening the store at term+1 raises the
        # fence on every replica member.
        new = GcsServer(session_name="ha", persist_path=path,
                        persist_backend="replicated", term=old.leader_term + 1)
        await new.start()

        rejections = 0
        for i in range(3):
            with pytest.raises(rpc.StaleLeaderError):
                await conn.call(
                    "KVPut", {"ns": "", "key": f"post{i}", "value": b"2"},
                    timeout=10,
                )
            rejections += 1
        assert rejections == 3

        # The old primary noticed the fence and demoted: serve loop done.
        for _ in range(100):
            if old.fenced and old._stopping:
                break
            await asyncio.sleep(0.05)
        assert old.fenced and old._stopping

        # No stale write leaked into the new leader's view; pre-fence
        # acknowledged state is intact.
        assert new.kv.get(("", "pre")) == b"1"
        assert not any(key.startswith("post") for _, key in new.kv)
        assert gcs_ha.read_leadership(new.store)["term"] == new.leader_term

        await conn.close()
        await new.stop()

    asyncio.run(go())


def test_restart_in_place_bumps_term(ha_env):
    """A replicated-backend GCS restarted over the same files must come
    back at a HIGHER term: its old incarnation may still think it leads."""
    path = ha_env

    async def go():
        s1 = GcsServer(session_name="ha", persist_path=path,
                       persist_backend="replicated")
        await s1.start()
        assert s1.leader_term == 1
        await s1.crash()
        s2 = GcsServer(session_name="ha", persist_path=path,
                       persist_backend="replicated")
        await s2.start()
        assert s2.leader_term == 2
        await s2.stop()

    asyncio.run(go())


def test_resubscribe_term_change_forces_snapshot(ha_env):
    """Satellite (a): on resubscribe, a changed leader term is
    unconditionally stale — snapshot pull even when epoch/seq line up."""
    path = ha_env

    async def go():
        server = GcsServer(session_name="ha", persist_path=path,
                           persist_backend="replicated")
        await server.start()
        conn = await rpc.connect(*server.server.address)
        client = GcsClient(conn)
        await client.subscribe("syncer:nodes", lambda m: None)
        channel = "syncer:nodes"
        assert client._sub_term[channel] == server.leader_term

        gaps = []
        client._note_gap = lambda ch, why: gaps.append((ch, why))
        # Same epoch, same seq, NEW term -> mandatory snapshot pull.
        client._check_resubscribe(channel, {
            "seq": client._sub_seq[channel],
            "pub_epoch": client._sub_epoch[channel],
            "leader_term": server.leader_term + 1,
        })
        assert gaps == [(channel, "resubscribe")]
        assert client._sub_term[channel] == server.leader_term + 1

        # Same term + same seq (the no-failover happy path) is NOT stale.
        gaps.clear()
        client._check_resubscribe(channel, {
            "seq": client._sub_seq[channel],
            "pub_epoch": client._sub_epoch[channel],
            "leader_term": server.leader_term + 1,
        })
        assert gaps == []

        await client.close()
        await server.stop()

    asyncio.run(go())


def test_stale_term_publish_dropped(ha_env):
    """Satellite (a): a pre-failover message straggling in after promotion
    (lower leader term) is dropped, never delivered to handlers."""
    path = ha_env

    async def go():
        server = GcsServer(session_name="ha", persist_path=path,
                           persist_backend="replicated")
        await server.start()
        conn = await rpc.connect(*server.server.address)
        client = GcsClient(conn)
        seen = []
        await client.subscribe("chan", seen.append)
        term = server.leader_term

        # Fresh-term message delivers; known term advances with it.
        await client._dispatch_pub("chan", {"v": 1, "leader_term": term + 1}, 1)
        # A stale pre-failover straggler (lower term) must be dropped.
        await client._dispatch_pub("chan", {"v": 2, "leader_term": term}, 2)
        assert [m["v"] for m in seen] == [1]

        await client.close()
        await server.stop()

    asyncio.run(go())


# -- driver-level failover ---------------------------------------------------


@pytest.fixture
def ray_ha(shutdown_only, monkeypatch):
    monkeypatch.setenv("RAY_TPU_GCS_PERSIST_BACKEND", "replicated")
    monkeypatch.setenv("RAY_TPU_GCS_LEADER_LEASE_S", "1.0")
    monkeypatch.setenv("RAY_TPU_GCS_STANDBY_POLL_S", "0.05")
    config.refresh()
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield
    ray_tpu.shutdown()  # before the env reverts: teardown needs HA config
    monkeypatch.undo()
    config.refresh()


def _kill_gcs_host():
    w = worker_mod.global_worker
    node = w.node
    return w.run_async(node.kill_gcs_host(), timeout=60)


def test_driver_cluster_survives_gcs_host_loss(ray_ha):
    """Whole-machine GCS loss under a live driver cluster: the standby
    promotes, raylet/driver/worker clients re-target via the leader file,
    and work — including in-flight sends that died mid-failover — resumes
    with state intact."""

    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(1)) == 2
    w = worker_mod.global_worker
    w.run_async(w.core.gcs.kv_put("stay", b"put-before-failover", ns="ha"))

    node = worker_mod.global_worker.node
    old_term = node.gcs_server.leader_term
    _kill_gcs_host()
    assert node.gcs_server.leader_term == old_term + 1

    deadline = time.monotonic() + 30
    while True:
        try:
            assert ray_tpu.get(f.remote(41), timeout=30) == 42
            break
        except Exception:
            if time.monotonic() > deadline:
                raise
    assert (
        w.run_async(w.core.gcs.kv_get("stay", ns="ha"), timeout=30)
        == b"put-before-failover"
    )


def test_lease_during_failover_granted_exactly_once(ray_ha):
    """Satellite (b): tasks whose control-plane traffic (lease, telemetry,
    deadline-stat sends) straddles the failover retry against the new
    leader per their wire retry class and run exactly once each."""
    import collections

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.calls = collections.Counter()

        def mark(self, i):
            self.calls[i] += 1
            return i

        def all(self):
            return dict(self.calls)

    c = Counter.remote()
    # Launch work, fail over while it is in flight, launch more.
    first = [c.mark.remote(i) for i in range(8)]
    _kill_gcs_host()
    second = [c.mark.remote(i) for i in range(8, 16)]
    assert sorted(ray_tpu.get(first + second, timeout=60)) == list(range(16))
    calls = ray_tpu.get(c.all.remote(), timeout=30)
    # Exactly once: no mark ran twice (a duplicated grant would double-run).
    assert calls == {i: 1 for i in range(16)}


# -- quorum HA: standby pools, majority loss, RPC-fed standbys ---------------


def test_two_consecutive_failovers_same_standby_pool(ha_env):
    """Regression: a standby that loses the promotion race (or whose
    promotion attempt fails) must RE-ARM at the next term, not give up
    forever — the same two-standby pool must absorb two failovers."""
    from ray_tpu._private.gcs_store import drop_host

    path = ha_env

    async def go():
        primary = GcsServer(session_name="ha", persist_path=path,
                            persist_backend="replicated")
        await primary.start()
        sb1 = gcs_ha.GcsStandby(session_name="ha", persist_path=path)
        sb2 = gcs_ha.GcsStandby(session_name="ha", persist_path=path)
        await sb1.start()
        await sb2.start()

        conn = await rpc.connect(*primary.server.address)
        await conn.call("KVPut", {"ns": "", "key": "k1", "value": b"v1"})
        await conn.close()

        # Failover #1: both standbys race; try_claim_term lets exactly one
        # open the store at term 2, the loser re-enters its watch loop.
        await primary.crash()
        drop_host(path)
        deadline = time.monotonic() + 30
        while not (sb1.promoted.is_set() or sb2.promoted.is_set()):
            assert time.monotonic() < deadline, "no standby promoted"
            await asyncio.sleep(0.05)
        winner, loser = (sb1, sb2) if sb1.promoted.is_set() else (sb2, sb1)
        new1 = winner.server
        assert new1.leader_term == 2
        assert not loser.promoted.is_set()

        conn = await rpc.connect(*new1.server.address)
        await conn.call("KVPut", {"ns": "", "key": "k2", "value": b"v2"})
        await conn.close()

        # Failover #2 through the SAME pool: the first race's loser must
        # still be armed and take term 3.
        await new1.crash()
        drop_host(path)
        await asyncio.wait_for(loser.promoted.wait(), 30)
        new2 = loser.server
        assert new2.leader_term == 3
        assert new2.kv.get(("", "k1")) == b"v1"
        assert new2.kv.get(("", "k2")) == b"v2"

        await winner.stop()
        await loser.stop()

    asyncio.run(go())


def test_majority_loss_demotes_leader_server(ha_env):
    """Graceful degradation's hard edge: with EVERY follower partitioned no
    majority can hold a commit — the leader must demote (typed rejection
    to clients, serve loop exits), never ack unreplicated writes."""
    from ray_tpu._private import gcs_store
    from ray_tpu._private.gcs_store import follower_paths, partition_host

    path = ha_env

    async def go():
        server = GcsServer(session_name="ha", persist_path=path,
                           persist_backend="replicated")
        await server.start()
        conn = await rpc.connect(*server.server.address)
        await conn.call("KVPut", {"ns": "", "key": "pre", "value": b"1"})
        await asyncio.sleep(0.1)  # let the pre write's group commit land
        try:
            for fol in follower_paths(path):
                partition_host(fol)
            # Batch sync: the RPC reply can precede the group commit, so
            # this write may be accepted in-memory — but its flush finds no
            # majority and the leader must demote instead of limping on.
            try:
                await conn.call(
                    "KVPut", {"ns": "", "key": "lost", "value": b"2"},
                    timeout=10,
                )
            except (rpc.StaleLeaderError, rpc.RpcError, OSError):
                pass
            for _ in range(200):
                if server.fenced and server._stopping:
                    break
                await asyncio.sleep(0.05)
            assert server.fenced and server._stopping
            # The demoted leader never shipped the unreplicated write: no
            # member of the (partitioned) majority holds it, while the
            # quorum-acked pre-partition write is on every follower.
            for fol in follower_paths(path):
                with open(fol, "rb") as f:
                    tables, _, _, _ = gcs_store._parse_replicated(f.read())
                assert "\x00pre" in tables.get("kv", {})
                assert "\x00lost" not in tables.get("kv", {})
        finally:
            gcs_store.heal_all_partitions()
            await conn.close()
            await server.stop()

    asyncio.run(go())


def test_standby_rpc_stream_mirrors_commits(ha_env):
    """The cross-process standby feed: a ShipSubscribe'd standby bootstraps
    from ShipSnapshot and then mirrors every quorum commit from pushed
    ShipFrames — no reliance on reading the leader's local files."""
    path = ha_env

    async def go():
        server = GcsServer(session_name="ha", persist_path=path,
                           persist_backend="replicated")
        await server.start()
        standby = gcs_ha.GcsStandby(session_name="ha", persist_path=path,
                                    mode="rpc")
        await standby.start()
        conn = await rpc.connect(*server.server.address)
        # Let the standby's watch loop dial and subscribe first so the
        # puts arrive as streamed frames, not just the bootstrap snapshot.
        deadline = time.monotonic() + 30
        while standby.snapshots_pulled == 0:
            assert time.monotonic() < deadline, "standby never subscribed"
            await asyncio.sleep(0.05)
        for i in range(3):
            await conn.call(
                "KVPut", {"ns": "", "key": f"k{i}", "value": b"v"}
            )
        while standby.mirror.seq < server.store.seq:
            assert time.monotonic() < deadline, "mirror never caught up"
            await asyncio.sleep(0.05)
        assert standby.frames_received > 0
        assert standby.mirror.term == server.store.term
        await conn.close()
        await standby.stop()
        await server.stop()

    asyncio.run(go())


def test_os_process_standby_promotes_after_host_loss(ha_env):
    """E2E with a REAL second process: ``python -m ray_tpu._private.gcs_ha``
    arms a standby in its own OS process; when the leader host dies the
    subprocess promotes, flips the leader file, and serves the acked state
    to clients that re-target through it."""
    import sys

    from ray_tpu._private import gcs_store
    from ray_tpu._private.gcs_store import drop_host, follower_paths

    path = ha_env
    leader_file = gcs_ha.leader_file_path(path)

    async def go():
        primary = GcsServer(session_name="ha", persist_path=path,
                            persist_backend="replicated")
        await primary.start()
        conn = await rpc.connect(*primary.server.address)
        await conn.call("KVPut", {"ns": "", "key": "k", "value": b"v"})
        await conn.close()
        old_addr = gcs_ha.resolve_leader_file(leader_file)
        assert old_addr == primary.server.address

        env = dict(
            os.environ,
            RAY_TPU_GCS_PERSIST_BACKEND="replicated",
            RAY_TPU_GCS_LEADER_LEASE_S="1.0",
            RAY_TPU_GCS_STANDBY_POLL_S="0.05",
            JAX_PLATFORMS="cpu",
        )
        proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "ray_tpu._private.gcs_ha",
            "--persist-path", path, "--session", "ha",
            env=env,
        )
        try:
            await primary.crash()
            drop_host(path)
            deadline = time.monotonic() + 30
            new_addr = None
            while time.monotonic() < deadline:
                addr = gcs_ha.resolve_leader_file(leader_file)
                if addr is not None and addr != old_addr:
                    new_addr = addr
                    break
                await asyncio.sleep(0.1)
            assert new_addr is not None, "subprocess standby never promoted"

            conn2 = await rpc.connect(*new_addr)
            reply = await conn2.call(
                "KVGet", {"ns": "", "key": "k"}, timeout=10
            )
            assert reply.get("value") == b"v"
            await conn2.close()
            tailer = gcs_store.ReplicaTailer(follower_paths(path)[0])
            tailer.poll()
            assert gcs_ha.read_leadership(tailer)["term"] == 2
        finally:
            proc.terminate()
            await proc.wait()

    asyncio.run(go())
