"""Object-store core tests: allocator, lifecycle, LRU eviction — run against
BOTH the native C++ engine and the pure-Python fallback (analog of the
reference's plasma allocator/eviction C++ unit tests)."""

import numpy as np
import pytest

from ray_tpu._private import store_core as sc

ENGINES = [pytest.param(sc.PyStoreCore, id="python")]
if sc.NATIVE:
    ENGINES.append(pytest.param(sc.NativeStoreCore, id="native"))


@pytest.fixture(params=ENGINES)
def Store(request):
    return request.param


def test_alloc_free_roundtrip(Store):
    s = Store(1 << 20)
    off_a = s.alloc("a", 1000, True)
    assert off_a >= 0
    off_b = s.alloc("b", 2000, False)
    assert off_b >= off_a + 1000
    assert s.used == 3000
    assert s.num_objects == 2
    s.seal("a")
    assert s.lookup("a") == (off_a, 1000, True, True)
    assert s.contains("a") and not s.contains("b")  # b unsealed
    assert s.free("a") == 1000
    assert s.lookup("a") is None
    assert s.used == 2000


def test_duplicate_alloc_raises(Store):
    s = Store(1 << 16)
    s.alloc("x", 10, True)
    with pytest.raises(KeyError):
        s.alloc("x", 10, True)


def test_capacity_exhaustion_and_reuse(Store):
    s = Store(64 * 10)  # ten 64B-rounded slots
    offs = [s.alloc(f"o{i}", 64, False) for i in range(10)]
    assert all(o >= 0 for o in offs)
    assert s.alloc("overflow", 64, False) == -1
    s.free("o5")
    off = s.alloc("overflow", 64, False)
    assert off == offs[5]  # best-fit reuses the freed slot


def test_coalescing(Store):
    s = Store(64 * 8)
    for i in range(8):
        s.alloc(f"o{i}", 64, False)
    # Free three adjacent slots -> one coalesced span fits a 3-slot object.
    for i in (2, 3, 4):
        s.free(f"o{i}")
    off = s.alloc("big", 64 * 3, False)
    assert off >= 0
    frag, largest, spans = s.fragmentation()
    assert largest == 0 and s.used == s.capacity


def test_lru_eviction_order_and_pinning(Store):
    s = Store(1 << 20)
    for i in range(5):
        s.alloc(f"o{i}", 100, False)
        s.seal(f"o{i}")
    s.pin("o0")
    s.touch("o1")  # o1 becomes most-recent
    victims = s.evict(250, 0)
    # o0 pinned, o1 freshly touched -> oldest unpinned are o2, o3, o4...
    assert victims[:2] == ["o2", "o3"]
    assert "o0" not in victims and "o1" not in victims


def test_evict_skips_unsealed(Store):
    s = Store(1 << 16)
    s.alloc("unsealed", 100, False)
    s.alloc("sealed", 100, False)
    s.seal("sealed")
    victims = s.evict(10_000, 0)
    assert victims == ["sealed"]
    assert s.lookup("unsealed") is not None


def test_arena_store_end_to_end(ray_start_regular):
    """Large objects round-trip through the node arena zero-copy."""
    import ray_tpu
    from ray_tpu._private import worker as worker_mod

    arr = np.random.rand(512, 512)  # 2 MB -> plasma path
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(arr, out)

    # The raylet's store core accounts for it.
    raylet = worker_mod.global_worker.node.raylet
    assert raylet.store.num_objects >= 1
    assert raylet.store_used >= arr.nbytes

    # Worker-side round trip too (task returns large value).
    @ray_tpu.remote
    def make():
        return np.ones((256, 256))

    np.testing.assert_array_equal(ray_tpu.get(make.remote()), np.ones((256, 256)))


def test_delete_quarantine(ray_start_regular):
    """Deleted objects vanish from the directory immediately but their arena
    bytes are not recycled within the grace window (zero-copy view safety)."""
    import ray_tpu
    from ray_tpu._private import worker as worker_mod

    arr = np.arange(300_000, dtype=np.float64)  # 2.4MB -> arena
    ref = ray_tpu.put(arr)
    view = ray_tpu.get(ref)  # zero-copy view into the arena
    raylet = worker_mod.global_worker.node.raylet

    # Drop the ref -> owner ref count hits zero -> delete path.
    del ref
    import gc, time as _t

    gc.collect()
    deadline = _t.monotonic() + 10
    while _t.monotonic() < deadline and not raylet.condemned:
        _t.sleep(0.2)
    assert raylet.condemned, "deleted object was not quarantined"
    # The view must still read the original bytes (span not recycled).
    np.testing.assert_array_equal(view[:100], np.arange(100, dtype=np.float64))
