"""Offline RL from logged transitions: MARWIL's advantage-weighted
imitation (or swap in CQLConfig / BCConfig — same offline_data input).

Run:  python examples/offline_rl.py
"""

import gymnasium as gym
import numpy as np

import ray_tpu
import ray_tpu.data as rd
from ray_tpu.rllib import MARWILConfig

if __name__ == "__main__":
    ray_tpu.init()
    env = gym.make("CartPole-v1")
    rows, (obs, _) = [], env.reset(seed=0)
    for _ in range(2000):
        action = int(obs[2] + 0.3 * obs[3] > 0)  # scripted demonstrator
        nxt, rew, term, trunc, _ = env.step(action)
        rows.append({
            "obs": obs.astype(np.float32).tolist(), "actions": action,
            "rewards": float(rew),
            "next_obs": nxt.astype(np.float32).tolist(),
            "dones": bool(term or trunc),
        })
        obs = nxt if not (term or trunc) else env.reset()[0]

    algo = (
        MARWILConfig()
        .environment("CartPole-v1")
        .offline_data(input_=rd.from_items(rows))
        .training(train_batch_size=256, updates_per_iteration=16)
        .build_algo()
    )
    for i in range(20):
        metrics = algo.train()
        print(i, {k: round(v, 3) for k, v in metrics.items()
                  if isinstance(v, float)})
    print("eval:", algo.evaluate(num_steps=500))
    algo.stop()
    ray_tpu.shutdown()
