"""Distributed GPT-style training: JaxTrainer gangs one worker per TPU
host, rendezvous over the xla collective backend, and runs ONE
jit/shard_map program over the pod mesh (DP/FSDP/TP/SP are mesh axes).

Run:  python examples/train_transformer.py
"""

import jax
import jax.numpy as jnp
import optax

import ray_tpu
from ray_tpu import train
from ray_tpu.air import RunConfig, ScalingConfig
from ray_tpu.train.jax import JaxTrainer


def train_fn(config):
    from ray_tpu.models import TransformerConfig, make_train_step
    from ray_tpu.parallel import make_mesh

    cfg = TransformerConfig(
        vocab_size=1024, d_model=256, n_layers=4, n_heads=8,
        max_seq_len=256, dtype=jnp.bfloat16, remat=True,
    )
    mesh = make_mesh({"data": jax.device_count()})
    init_state, step, shardings = make_train_step(cfg, mesh, optax.adamw(3e-4))
    state = init_state(jax.random.PRNGKey(0))

    rng = jax.random.PRNGKey(1)
    for i in range(config.get("steps", 20)):
        rng, k = jax.random.split(rng)
        raw = jax.random.randint(k, (8, 257), 0, cfg.vocab_size)
        batch = {
            "tokens": jax.device_put(raw[:, :-1], shardings["tokens"]),
            "targets": jax.device_put(raw[:, 1:], shardings["tokens"]),
        }
        state, metrics = step(state, batch)
        if i % 5 == 0:
            train.report({"step": i, "loss": float(metrics["loss"])})
    train.report({"final_loss": float(metrics["loss"])})


if __name__ == "__main__":
    ray_tpu.init()
    result = JaxTrainer(
        train_fn,
        train_loop_config={"steps": 20},
        scaling_config=ScalingConfig(num_workers=1),  # one per TPU host
        run_config=RunConfig(name="gpt_demo", storage_path="/tmp/rt_demo"),
    ).fit()
    print("metrics:", result.metrics)
    ray_tpu.shutdown()
