"""Serve a streaming deployment: SSE over HTTP via the Accept header, plus
the typed gRPC PredictStreaming rpc from the same replica.

Run:  python examples/serve_streaming.py
Then: curl -N -H 'Accept: text/event-stream' -d 'ignored' \
        http://127.0.0.1:8000/tokens
"""

import time

import ray_tpu
from ray_tpu import serve

if __name__ == "__main__":
    ray_tpu.init()
    serve.start(http_options={"host": "127.0.0.1", "port": 8000})

    @serve.deployment(num_replicas=2)
    class Tokens:
        def __call__(self, request):
            for tok in ["hello", "from", "ray_tpu", "serve"]:
                yield tok

    serve.run(Tokens.bind(), name="tokens", route_prefix="/tokens")
    print("serving on http://127.0.0.1:8000/tokens (ctrl-c to exit)")
    while True:
        time.sleep(5)
